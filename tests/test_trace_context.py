"""Distributed trace context: ambient propagation, the EDL1 wire
(client inject → server re-establish, including nested hops and the
chunked-RPC path), thread isolation, and the env handoff the launcher
uses to pull spawned trainers into a resize epoch's trace."""

import functools
import json
import threading
import time

import pytest

from edl_tpu.obs import context as obs_context
from edl_tpu.obs import trace as obs_trace
from edl_tpu.rpc import chunks
from edl_tpu.rpc.client import RpcClient
from edl_tpu.rpc.server import RpcServer


@pytest.fixture
def make_server():
    servers = []

    def make() -> RpcServer:
        srv = RpcServer("127.0.0.1", 0)
        servers.append(srv)
        return srv

    yield make
    for srv in servers:
        srv.stop()


@pytest.fixture(autouse=True)
def clean_process_root():
    yield
    obs_context.set_process_root(None)


def _read_events(path):
    return [json.loads(line) for line in path.read_text().splitlines()]


# -- context basics ----------------------------------------------------------

def test_child_keeps_trace_links_parent():
    root = obs_context.new_trace(stage="s1")
    child = root.child()
    assert child.trace_id == root.trace_id
    assert child.parent_id == root.span_id
    assert child.span_id != root.span_id
    assert child.baggage == {"stage": "s1"}


def test_wire_and_env_roundtrip():
    ctx = obs_context.new_trace(job="j")
    back = obs_context.TraceContext.from_wire(ctx.to_wire())
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    assert back.baggage == {"job": "j"}
    env = obs_context.TraceContext.from_env_value(ctx.to_env())
    assert env.trace_id == ctx.trace_id
    # garbage never raises — a bad peer can't crash a handler
    assert obs_context.TraceContext.from_wire(None) is None
    assert obs_context.TraceContext.from_wire({"t": 1, "s": "x"}) is None
    assert obs_context.TraceContext.from_env_value("not json") is None


def test_use_restores_previous_context():
    a, b = obs_context.new_trace(), obs_context.new_trace()
    assert obs_context.current() is None
    with obs_context.use(a):
        assert obs_context.current().trace_id == a.trace_id
        with obs_context.use(b):
            assert obs_context.current().trace_id == b.trace_id
        assert obs_context.current().trace_id == a.trace_id
    assert obs_context.current() is None
    with obs_context.use(None):   # None is a no-op branch-free call site
        assert obs_context.current() is None


def test_process_root_is_fallback_for_new_threads():
    root = obs_context.new_trace()
    obs_context.set_process_root(root)
    seen = {}

    def worker():
        seen["ctx"] = obs_context.current()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen["ctx"].trace_id == root.trace_id
    # an explicitly attached context beats the root
    other = obs_context.new_trace()
    with obs_context.use(other):
        assert obs_context.current().trace_id == other.trace_id


def test_install_from_env(monkeypatch):
    ctx = obs_context.new_trace(stage="e1")
    monkeypatch.setenv(obs_context.ENV_VAR, ctx.to_env())
    got = obs_context.install_from_env()
    assert got.trace_id == ctx.trace_id
    assert obs_context.current().trace_id == ctx.trace_id


# -- tracer integration ------------------------------------------------------

def test_tracer_attaches_ids_only_with_context(tmp_path):
    tr = obs_trace.Tracer(str(tmp_path / "t.jsonl"), "unit")
    tr.emit("plain", at=1.0)
    ctx = obs_context.new_trace()
    with obs_context.use(ctx):
        tr.emit("traced", at=2.0)
    tr.close()
    plain, traced = _read_events(tmp_path / "t.jsonl")
    assert "trace_id" not in plain and "span_id" not in plain
    assert traced["trace_id"] == ctx.trace_id
    assert traced["span_id"] == ctx.span_id


def test_nested_spans_link_parents(tmp_path):
    tr = obs_trace.Tracer(str(tmp_path / "t.jsonl"), "unit")
    ctx = obs_context.new_trace()
    with obs_context.use(ctx):
        with tr.span("outer"):
            with tr.span("inner"):
                pass
    tr.close()
    inner, outer = _read_events(tmp_path / "t.jsonl")  # inner exits first
    assert inner["name"] == "inner" and outer["name"] == "outer"
    assert inner["trace_id"] == outer["trace_id"] == ctx.trace_id
    assert inner["parent_id"] == outer["span_id"]
    assert outer["parent_id"] == ctx.span_id
    # span ts is the BEGIN: outer started no later than inner
    assert outer["ts"] <= inner["ts"]


# -- the wire ----------------------------------------------------------------

def test_rpc_handler_inherits_caller_trace(make_server, tmp_path):
    tr = obs_trace.configure(str(tmp_path / "srv.jsonl"), "server")
    try:
        def handler():
            obs_trace.emit("srv/handled")
            cur = obs_context.current()
            return {"trace": cur.trace_id if cur else None,
                    "parent": cur.parent_id if cur else None}

        srv = make_server()
        srv.register("do", handler)
        srv.start()
        ctx = obs_context.new_trace()
        with RpcClient(f"127.0.0.1:{srv.port}") as client:
            with obs_context.use(ctx):
                r = client.call("do")
            # outside any context the handler must see none
            r_none = client.call("do")
        assert r["trace"] == ctx.trace_id
        assert r["parent"] == ctx.span_id      # handler runs as a child span
        assert r_none["trace"] is None, "context leaked across requests"
    finally:
        obs_trace.install(obs_trace.NullTracer())
        tr.close()
    with_ctx, without_ctx = [e for e in _read_events(tmp_path / "srv.jsonl")
                             if e["name"] == "srv/handled"]
    assert with_ctx["trace_id"] == ctx.trace_id
    assert "trace_id" not in without_ctx


def test_nested_client_server_client_hop_keeps_trace(make_server):
    inner = make_server()
    inner.register("leaf", lambda: {
        "trace": obs_context.current().trace_id
        if obs_context.current() else None})
    inner.start()

    def middle():
        with RpcClient(f"127.0.0.1:{inner.port}") as c:
            return c.call("leaf")

    outer = make_server()
    outer.register("mid", middle)
    outer.start()
    ctx = obs_context.new_trace()
    with obs_context.use(ctx), RpcClient(f"127.0.0.1:{outer.port}") as c:
        r = c.call("mid")
    assert r["trace"] == ctx.trace_id, "trace lost across the second hop"


def test_chunked_rpc_path_carries_context(make_server):
    got: list[tuple[int, str | None]] = []
    buf = bytearray()

    def push(seq: int, data: bytes, eof: bool):
        cur = obs_context.current()
        got.append((seq, cur.trace_id if cur else None))
        buf.extend(data)
        return {"ok": True}

    def fetch(offset: int, length: int) -> bytes:
        cur = obs_context.current()
        got.append((-1, cur.trace_id if cur else None))
        return bytes(buf[offset:offset + length])

    srv = make_server()
    srv.register("push", push)
    srv.register("fetch", fetch)
    srv.start()
    payload = bytes(range(256)) * 40
    ctx = obs_context.new_trace()
    with obs_context.use(ctx), RpcClient(f"127.0.0.1:{srv.port}") as c:
        n = chunks.push_bytes(functools.partial(c.call, "push"), payload,
                              chunk_bytes=1024)
        back = chunks.fetch_bytes(functools.partial(c.call, "fetch"),
                                  len(payload), chunk_bytes=1024)
    assert n > 1 and back == payload
    assert got and all(t == ctx.trace_id for _, t in got), \
        "every chunk RPC must carry the ambient trace"


def test_concurrent_handlers_never_cross_contexts(make_server):
    def slow_echo(tag: str):
        time.sleep(0.02)
        cur = obs_context.current()
        return {"tag": tag, "trace": cur.trace_id if cur else None}

    srv = make_server()
    srv.register("echo", slow_echo)
    srv.start()
    errors: list[str] = []

    def client_loop(i: int):
        ctx = obs_context.new_trace()
        try:
            with RpcClient(f"127.0.0.1:{srv.port}") as c:
                for _ in range(10):
                    with obs_context.use(ctx):
                        r = c.call("echo", tag=str(i))
                    if r["trace"] != ctx.trace_id:
                        errors.append(
                            f"client {i} saw {r['trace']}")
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    threads = [threading.Thread(target=client_loop, args=(i,))
               for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
