"""Control-plane scale observatory: sim package units, the coord/watch
telemetry it instruments, watch-based aggregator discovery, and the
TSDB fleet-cardinality guard rails (PR 16)."""

import json
import os
import threading
import time

import pytest

from edl_tpu.cluster import paths
from edl_tpu.coord import memory as coord_memory
from edl_tpu.coord.memory import MemoryKV
from edl_tpu.coord.server import _table_of
from edl_tpu.obs import advert
from edl_tpu.obs.metrics import parse_exposition
from edl_tpu.sim.actor import OpRecorder, PodActor, TimedStore, table_of_key
from edl_tpu.sim.harness import latency_stats
from edl_tpu.sim.report import (classify, fit_exponent, render_report,
                                SUPER_LINEAR_ALPHA)
from edl_tpu.utils import constants

JOB = "sim-test"


# -- actor / recorder units --------------------------------------------------

def test_table_of_key_bounded_cardinality():
    assert table_of_key(paths.key(JOB, "heartbeat", "pod-1")) == "heartbeat"
    assert table_of_key(paths.key(JOB, "obs", "metrics/x")) == "obs"
    assert table_of_key("/elsewhere/foo") == "other"
    assert table_of_key(paths.ROOT + f"/{JOB}/nonsense/x") == "other"
    assert table_of_key("") == ""


def test_server_table_of_matches_wire_kwargs():
    assert _table_of({"key": paths.key(JOB, "resource", "p")}) == "resource"
    assert _table_of({"prefix": paths.table_prefix(JOB, "obs")}) == "obs"
    assert _table_of({"guard_key": paths.key(JOB, "rank", "0")}) == "rank"
    assert _table_of({"ttl": 5}) == ""
    assert _table_of({"key": "/other/shape"}) == "other"


def test_timed_store_records_ops_and_failures():
    kv = MemoryKV()
    rec = OpRecorder()
    store = TimedStore(kv, rec)
    store.put(paths.key(JOB, "heartbeat", "p0"), b"x")
    store.get(paths.key(JOB, "cluster", "spec"))
    lease = store.lease_grant(5.0)
    store.lease_keepalive(lease)

    class _Boom(MemoryKV):
        def put(self, key, value, lease=0):
            raise RuntimeError("boom")

    with pytest.raises(RuntimeError):
        TimedStore(_Boom(), rec).put(paths.key(JOB, "heartbeat", "x"), b"v")
    durations, failures = rec.snapshot()
    assert ("put", "heartbeat") in durations
    assert ("get", "cluster") in durations
    assert ("lease_grant", "") in durations
    assert failures.get(("put", "heartbeat")) == 1
    assert rec.failure_count == 1
    _d, f = rec.snapshot(reset=True)
    assert rec.failure_count == 0


def test_pod_actor_lifecycle_and_op_mix():
    kv = MemoryKV()
    rec = OpRecorder()
    actor = PodActor(TimedStore(kv, rec), JOB, "pod-0", ttl=5.0,
                     heartbeat_period=0.01, status_period=0.01,
                     read_period=0.01)
    actor.start()
    assert kv.get(paths.key(JOB, constants.ETCD_POD_RESOURCE,
                            "pod-0")) is not None
    time.sleep(0.02)
    actor.tick()
    hb = kv.get(paths.key(JOB, constants.ETCD_HEARTBEAT, "pod-0"))
    assert hb is not None and json.loads(hb.value.decode())["beat"] == 1
    assert kv.get(paths.key(JOB, constants.ETCD_TRAIN_STATUS,
                            "pod-0")) is not None
    durations, failures = rec.snapshot()
    assert ("get", "cluster") in durations  # FleetView-style read
    assert not failures
    actor.stop()
    # lease revoked with the session: the advert must expire with it
    assert kv.get(paths.key(JOB, constants.ETCD_POD_RESOURCE,
                            "pod-0")) is None


# -- report math -------------------------------------------------------------

def test_latency_stats_shape():
    s = latency_stats([0.004, 0.001, 0.002, 0.003])
    assert s["samples"] == 4
    assert s["p50_s"] == pytest.approx(0.003, abs=1e-3)
    assert s["max_s"] == pytest.approx(0.004)
    assert latency_stats([]) == {"samples": 0}


def test_fit_exponent_recovers_known_slopes():
    linear = [(10, 0.01), (100, 0.1), (1000, 1.0)]
    assert fit_exponent(linear) == pytest.approx(1.0, abs=1e-6)
    flat = [(10, 0.02), (100, 0.02), (1000, 0.02)]
    assert fit_exponent(flat) == pytest.approx(0.0, abs=1e-6)
    quadratic = [(10, 1.0), (100, 100.0)]
    assert fit_exponent(quadratic) == pytest.approx(2.0, abs=1e-6)
    assert fit_exponent([(10, 0.01)]) is None
    assert fit_exponent([(10, 0.0), (100, 0.0)]) is None
    assert classify(2.0) == "SUPER-LINEAR"
    assert classify(0.05) == "flat"
    assert classify(None) == "n/a"
    assert SUPER_LINEAR_ALPHA > 1.0


def test_render_report_flags_super_linear(tmp_path):
    artifact = {
        "schema": "edl-sim/1", "job_id": "t", "ts": 0.0,
        "host": {"cpus": 1}, "config": {"ns": [10, 100], "round_s": 1.0},
        "rounds": [
            {"n": 10, "op_failures": 0,
             "propagation": {"watch": latency_stats([0.001] * 4),
                             "poll": latency_stats([0.01] * 4)},
             "ops": {"put/heartbeat": latency_stats([0.001] * 4)},
             "lease_sweep": {"sweeps": 4, "mean_s": 1e-05,
                             "leases_live": 10, "swept": 0},
             "scrape": {"cycles": [{"wall_s": 0.01, "targets": 10,
                                    "errors": 0}],
                        "mean_wall_s": 0.01, "staleness_floor_s": 0.01},
             "alert_dispatch": latency_stats([0.02])},
            {"n": 100, "op_failures": 0,
             "propagation": {"watch": latency_stats([0.0011] * 4),
                             "poll": latency_stats([1.0] * 4)},
             "ops": {"put/heartbeat": latency_stats([0.0011] * 4)},
             "lease_sweep": {"sweeps": 4, "mean_s": 1.2e-05,
                             "leases_live": 100, "swept": 0},
             "scrape": {"cycles": [{"wall_s": 0.1, "targets": 100,
                                    "errors": 0}],
                        "mean_wall_s": 0.1, "staleness_floor_s": 0.1},
             "alert_dispatch": latency_stats([0.2])},
        ],
    }
    text = render_report(artifact)
    assert "propagation/watch" in text and "flat" in text
    assert "SUPER-LINEAR" in text  # poll went 0.01 -> 1.0 over one decade
    # the standalone renderer parses the same artifact from disk
    p = tmp_path / "SIM_r01.json"
    p.write_text(json.dumps(artifact))
    from edl_tpu.sim import report as report_mod
    assert report_mod.main([str(p)]) == 0


# -- coord watch/lease telemetry (PR 16 instrumentation) ---------------------

def test_wait_watch_telemetry_moves():
    kv = MemoryKV()
    prefix = paths.table_prefix(JOB, constants.ETCD_POD_RESOURCE)
    key = paths.key(JOB, constants.ETCD_POD_RESOURCE, "p0")
    rev = kv.put(key, b"seed")
    # the gauge/counter are process-global: other tests in a full-suite
    # run may leave blocked daemon waiters behind, so assert DELTAS
    watchers0 = coord_memory._WATCHERS_G.value
    wakeups0 = coord_memory._WAKEUPS_TOTAL.value
    delivered = []

    def waiter():
        res = kv.wait(prefix, rev, 5.0)
        delivered.append(res)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    deadline = time.monotonic() + 2.0
    while (coord_memory._WATCHERS_G.value < watchers0 + 1
           and time.monotonic() < deadline):
        time.sleep(0.01)
    assert coord_memory._WATCHERS_G.value >= watchers0 + 1  # blocked watcher
    kv.put(key, b"changed")
    t.join(timeout=5.0)
    assert delivered and delivered[0].events
    assert coord_memory._WAKEUPS_TOTAL.value >= wakeups0 + 1
    assert coord_memory._WATCHERS_G.value <= watchers0  # ours unblocked


def test_lease_sweep_telemetry_moves():
    kv = MemoryKV(sweep_period=0.05)
    sweeps0 = coord_memory._LEASE_SWEEP_SECONDS.count
    swept0 = coord_memory._LEASES_SWEPT_TOTAL.value
    lease = kv.lease_grant(0.1)
    kv.put(paths.key(JOB, constants.ETCD_POD_RESOURCE, "dead"), b"x", lease)
    time.sleep(0.5)
    assert coord_memory._LEASE_SWEEP_SECONDS.count > sweeps0
    assert coord_memory._LEASES_SWEPT_TOTAL.value >= swept0 + 1
    assert kv.get(paths.key(JOB, constants.ETCD_POD_RESOURCE,
                            "dead")) is None


# -- watch-based aggregator discovery (satellite: advert watcher) ------------

def test_metrics_target_watcher_tracks_adverts():
    kv = MemoryKV()
    w = advert.MetricsTargetWatcher(kv, JOB, period=0.2).start()
    try:
        reg = advert.advertise_metrics(kv, JOB, "trainer", "1.2.3.4:9",
                                       name="t0", ttl=30.0)
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            targets = w.targets()
            if "t0" in targets:
                break
            time.sleep(0.02)
        assert w.targets()["t0"]["endpoint"] == "1.2.3.4:9"
        reg.stop()
        kv.delete(paths.key(JOB, constants.ETCD_OBS, "metrics/t0"))
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            if "t0" not in w.targets():
                break
            time.sleep(0.02)
        assert "t0" not in w.targets()
    finally:
        w.stop()


def test_metrics_target_watcher_poll_fallback():
    class NoWaitKV(MemoryKV):
        def wait(self, prefix, since_revision, timeout):
            raise NotImplementedError

    kv = NoWaitKV()
    advert.advertise_metrics(kv, JOB, "trainer", "5.6.7.8:9", name="t1",
                             ttl=30.0)
    w = advert.MetricsTargetWatcher(kv, JOB, period=0.1).start()
    try:
        deadline = time.monotonic() + 3.0
        while w._watch_ok and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not w._watch_ok  # permanently degraded to polling
        assert w.targets()["t1"]["endpoint"] == "5.6.7.8:9"  # via get_prefix
    finally:
        w.stop()


def test_aggregator_discovery_knob(monkeypatch):
    from edl_tpu.obs.agg import Aggregator
    kv = MemoryKV()
    advert.advertise_metrics(kv, JOB, "trainer", "9.9.9.9:1", name="t2",
                             ttl=30.0)
    monkeypatch.setenv("EDL_TPU_OBS_DISCOVERY_WATCH", "0")
    agg = Aggregator(kv, JOB, scrape_interval=0, incident_dir="",
                     enable_actions=False)
    assert agg._discover_targets()["t2"]["endpoint"] == "9.9.9.9:1"
    assert agg._target_watcher is None  # knob off: pure poll path
    agg.stop_loop()

    monkeypatch.setenv("EDL_TPU_OBS_DISCOVERY_WATCH", "1")
    agg = Aggregator(kv, JOB, scrape_interval=0, incident_dir="",
                     enable_actions=False)
    assert agg._discover_targets()["t2"]["endpoint"] == "9.9.9.9:1"
    assert agg._target_watcher is not None  # watch view lazily started
    agg.stop_loop()
    assert agg._target_watcher is None  # stop_loop stops the watcher


# -- /healthz coord block + edl-obs-top pane ---------------------------------

def test_coord_summary_block_and_top_pane():
    from edl_tpu.obs.agg import Aggregator
    from edl_tpu.obs.top import render_top
    agg = Aggregator(MemoryKV(), JOB, scrape_interval=0, incident_dir="",
                     enable_actions=False)
    try:
        page = (
            "# TYPE edl_kv_ops_total counter\n"
            'edl_kv_ops_total{component="coord",op="kv_put"} 42\n'
            "# TYPE edl_coord_watchers gauge\n"
            'edl_coord_watchers{component="coord"} 3\n'
            "# TYPE edl_coord_leases_live gauge\n"
            'edl_coord_leases_live{component="coord"} 17\n'
            "# TYPE edl_rpc_open_connections gauge\n"
            'edl_rpc_open_connections{component="coord"} 5\n'
            'edl_rpc_open_connections{component="data"} 99\n')
        coord = agg._coord_summary(parse_exposition(page))
        assert coord["ops_total"] == 42.0
        assert coord["watchers"] == 3.0
        assert coord["leases_live"] == 17.0
        assert coord["open_connections"] == 5.0  # data server filtered out
        # no coord component on the page -> no block at all
        assert agg._coord_summary(parse_exposition(
            'edl_kv_ops_total{component="data",op="kv_put"} 1\n')) == {}
        frame = render_top({"job_id": JOB, "live_targets": 1,
                            "coord": coord}, {"firing": []})
        assert "coord:" in frame and "leases=17" in frame
    finally:
        agg.stop_loop()


# -- TSDB fleet-cardinality guard rails (satellite: ~5k series) --------------

def test_tsdb_guardrail_5k_instance_series():
    from edl_tpu.obs.tsdb import TSDB
    tsdb = TSDB(retention_s=60.0)
    n_series = 5000
    parsed = {}
    for i in range(n_series):
        labels = (("component", "sim-pod"), ("instance", f"10.0.0.1:{i}"))
        parsed[("edl_sim_heartbeats_total", labels)] = float(i)
    t0 = time.perf_counter()
    for tick in range(3):
        tsdb.ingest({k: v + tick for k, v in parsed.items()},
                    ts=100.0 + tick)
    ingest_s = (time.perf_counter() - t0) / 3
    assert tsdb.series_count("edl_sim_heartbeats_total") == n_series
    # bound per-cycle ingestion at fleet cardinality: a 5k-target fleet
    # scraped every few seconds must not eat the scrape interval (the
    # generous bound absorbs CI-box noise; the regression this pins is
    # accidental O(series^2) work, which would blow far past it)
    assert ingest_s < 2.0, f"TSDB ingest took {ingest_s:.3f}s for 5k series"
    t0 = time.perf_counter()
    rates = tsdb.rate("edl_sim_heartbeats_total", 10.0, now=103.0,
                      min_coverage=0.0)
    rate_s = time.perf_counter() - t0
    assert rates and rate_s < 2.0, f"windowed rate took {rate_s:.3f}s"


def test_healthz_read_bounded_at_fleet_cardinality():
    from edl_tpu.obs.agg import Aggregator
    agg = Aggregator(MemoryKV(), JOB, scrape_interval=0, cache_s=30.0,
                     incident_dir="", enable_actions=False)
    try:
        for i in range(5000):
            labels = (("component", "sim-pod"),
                      ("instance", f"10.0.0.1:{i}"))
            agg.tsdb.ingest(
                {("edl_sim_heartbeats_total", labels): 1.0}, ts=100.0)
        agg.collect()  # warm the merged-page cache (cache_s=30)
        t0 = time.perf_counter()
        summary = agg.job_summary()
        healthz_s = time.perf_counter() - t0
        assert "job_id" in summary
        assert healthz_s < 2.0, \
            f"/healthz took {healthz_s:.3f}s at 5k-series cardinality"
    finally:
        agg.stop_loop()


# -- end-to-end: one tiny real round -----------------------------------------

def test_harness_round_end_to_end(tmp_path):
    """A real (subprocess) coord server + real aggregator under a tiny
    fleet: every signal present, artifact parseable by the renderer."""
    from edl_tpu.sim.harness import SimConfig, run_sweep
    cfg = SimConfig(ns=(4,), round_s=2.5, ttl=5.0, heartbeat_period=0.5,
                    propagation_trials=3, scrape_cycles=1, alert_trials=1,
                    stub_servers=2, clients=2, job_id="sim-e2e",
                    data_dir=str(tmp_path / "coord"))
    os.makedirs(cfg.data_dir, exist_ok=True)
    out = str(tmp_path / "SIM_e2e.json")
    artifact = run_sweep(cfg, out_path=out)
    assert artifact["schema"] == "edl-sim/1"
    (r,) = artifact["rounds"]
    assert r["n"] == 4
    assert r["op_failures"] == 0
    assert r["propagation"]["watch"]["samples"] > 0
    assert r["propagation"]["poll"]["samples"] > 0
    assert any(k.startswith("put/") for k in r["ops"])
    assert r["lease_sweep"]["sweeps"] > 0
    assert r["lease_sweep"]["leases_live"] >= 4
    assert r["scrape"]["cycles"] and r["scrape"]["cycles"][0]["targets"] >= 4
    assert r["alert_dispatch"]["samples"] >= 1  # rule fired + dispatched
    text = render_report(artifact)
    assert "growth exponent" in text
    with open(out) as f:
        assert json.load(f)["rounds"][0]["n"] == 4
