"""End-to-end launcher integration: real launcher subprocesses against a
real coordination server, inert trainers, exit-code fault injection,
and a live elastic resize.

Port of the reference's multi-process no-GPU strategy
(test_launch.sh:50-61, SURVEY.md §4): pods are processes, the cluster
is coordination-store state, trainers are inert.
"""

import os
import subprocess
import sys
import time

import pytest

from edl_tpu.cluster.status import Status, load_job_status
from edl_tpu.coord.client import CoordClient

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEMO = os.path.join(REPO, "tests", "helpers", "demo_trainer.py")

FAST = {
    "EDL_TPU_TTL": "1",
    "EDL_TPU_GENERATOR_PERIOD": "0.2",
    "EDL_TPU_WATCHER_PERIOD": "0.2",
    "EDL_TPU_SUPERVISOR_PERIOD": "0.2",
    "EDL_TPU_BARRIER_TIMEOUT": "40",
    "EDL_TPU_RESIZE_BARRIER_TIMEOUT": "30",
}


def spawn_launcher(job_id, coord_ep, tmp, name, nodes_range, extra_env=None):
    env = dict(os.environ)
    env.update(FAST)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    log = open(os.path.join(tmp, f"launcher-{name}.log"), "wb")
    proc = subprocess.Popen(
        [sys.executable, "-m", "edl_tpu.collective.launch",
         "--job_id", job_id, "--coord_endpoints", coord_ep,
         "--nodes_range", nodes_range, "--nproc_per_node", "1",
         "--log_dir", os.path.join(tmp, f"log-{name}"), DEMO],
        env=env, cwd=tmp, stdout=log, stderr=subprocess.STDOUT)
    proc._logfile = log  # noqa: SLF001 - keep handle for closing
    return proc


def finish(proc, timeout):
    try:
        ret = proc.wait(timeout=timeout)
    finally:
        proc._logfile.close()  # noqa: SLF001
    return ret


@pytest.fixture
def coord(coord_server):
    ep = f"127.0.0.1:{coord_server.port}"
    client = CoordClient(ep)
    yield ep, client
    client.close()


def _dump_logs(tmp):
    out = []
    for root, _, files in os.walk(tmp):
        for f in files:
            if f.endswith(".log") or f.startswith("workerlog"):
                p = os.path.join(root, f)
                out.append(f"==== {p} ====\n" + open(p, errors="replace").read())
    return "\n".join(out)


def test_two_pod_job_succeeds(coord, tmp_path):
    ep, client = coord
    tmp = str(tmp_path)
    env = {"EDL_TPU_DEMO_SLEEP": "2"}
    a = spawn_launcher("j-ok", ep, tmp, "a", "2:2", env)
    b = spawn_launcher("j-ok", ep, tmp, "b", "2:2", env)
    ra, rb = finish(a, 60), finish(b, 60)
    assert (ra, rb) == (0, 0), _dump_logs(tmp)
    assert load_job_status(client, "j-ok") == Status.SUCCEED

    # relaunching a SUCCEEDed job is a no-op (reference launch.py:44-47)
    c = spawn_launcher("j-ok", ep, tmp, "c", "2:2", env)
    assert finish(c, 30) == 0


def test_trainer_failure_flags_job_failed(coord, tmp_path):
    ep, client = coord
    tmp = str(tmp_path)
    a = spawn_launcher("j-fail", ep, tmp, "a", "2:2", {"EDL_TPU_DEMO_SLEEP": "3"})
    b = spawn_launcher("j-fail", ep, tmp, "b", "2:2",
                       {"EDL_TPU_DEMO_SLEEP": "1", "EDL_TPU_DEMO_EXIT_CODE": "7"})
    rb = finish(b, 60)
    ra = finish(a, 60)
    assert rb == 1, _dump_logs(tmp)
    assert load_job_status(client, "j-fail") == Status.FAILED


def test_elastic_recovery_overwrites_failed_flag(coord, tmp_path):
    """A pod failure mid-job flags FAILED provisionally, but when the
    survivors complete, the leader's final verdict (current members only)
    flips the job to SUCCEED — elastic recovery must not read as failure."""
    ep, client = coord
    tmp = str(tmp_path)
    a = spawn_launcher("j-recover", ep, tmp, "a", "1:2",
                       {"EDL_TPU_DEMO_SLEEP": "6", "EDL_TPU_DEMO_SLEEP_SOLO": "6"})
    b = spawn_launcher("j-recover", ep, tmp, "b", "1:2",
                       {"EDL_TPU_DEMO_SLEEP": "1", "EDL_TPU_DEMO_SLEEP_SOLO": "1",
                        "EDL_TPU_DEMO_EXIT_CODE": "7"})
    rb = finish(b, 60)
    ra = finish(a, 90)
    assert rb == 1 and ra == 0, _dump_logs(tmp)
    assert load_job_status(client, "j-recover") == Status.SUCCEED


def test_elastic_scale_out_restarts_trainers(coord, tmp_path):
    ep, client = coord
    tmp = str(tmp_path)
    marker_a = os.path.join(tmp, "marker-a.txt")
    marker_b = os.path.join(tmp, "marker-b.txt")
    # A starts solo (min 1) with a long solo sleep so B can join mid-run
    a = spawn_launcher("j-elastic", ep, tmp, "a", "1:2",
                       {"EDL_TPU_DEMO_SLEEP": "2", "EDL_TPU_DEMO_SLEEP_SOLO": "25",
                        "EDL_TPU_DEMO_MARKER": marker_a})
    # wait until A's solo trainer is actually running
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not os.path.exists(marker_a):
        time.sleep(0.2)
    assert os.path.exists(marker_a), _dump_logs(tmp)

    b = spawn_launcher("j-elastic", ep, tmp, "b", "1:2",
                       {"EDL_TPU_DEMO_SLEEP": "2", "EDL_TPU_DEMO_MARKER": marker_b})
    ra, rb = finish(a, 90), finish(b, 90)
    assert (ra, rb) == (0, 0), _dump_logs(tmp)
    assert load_job_status(client, "j-elastic") == Status.SUCCEED

    # A must have started twice: solo world=1, then resized world=2
    starts_a = open(marker_a).read().strip().splitlines()
    assert len(starts_a) == 2, starts_a
    assert "world=1" in starts_a[0] and "world=2" in starts_a[1]
    starts_b = open(marker_b).read().strip().splitlines()
    assert any("world=2" in s for s in starts_b)
