"""Mixture-of-experts MLP: routing invariants, grads, ep-mesh training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from edl_tpu.ops.moe import MoEMLP, compute_routing


def _probs(B=2, S=8, E=4, seed=0):
    logits = np.random.default_rng(seed).normal(size=(B, S, E))
    return jax.nn.softmax(jnp.asarray(logits, jnp.float32), axis=-1)


def test_routing_no_drops_with_ample_capacity():
    probs = _probs()
    B, S, E = probs.shape
    K = 2
    dispatch, combine, aux, drops = compute_routing(probs, K, capacity=S * K)
    # every (token, k) slot placed exactly once
    assert float(dispatch.sum()) == B * S * K
    # each slot in a distinct (e, c) cell
    assert float(dispatch.max()) == 1.0
    # combine weights per token sum to 1 (top-k gates renormalised)
    np.testing.assert_allclose(np.asarray(combine.sum(axis=(2, 3))), 1.0,
                               rtol=1e-5)
    assert float(aux) > 0


def test_routing_drops_over_capacity():
    probs = _probs(S=16)
    dispatch, combine, _, _ = compute_routing(probs, 2, capacity=2)
    B, S, E = probs.shape
    assert float(dispatch.sum()) < B * S * 2       # overflow dropped
    assert float(dispatch.sum(axis=(1, 3)).max()) <= 2 * 1  # per-expert cap
    # dropped tokens lose combine mass but never exceed 1
    assert float(combine.sum(axis=(2, 3)).max()) <= 1.0 + 1e-5


def test_routing_position_bound():
    probs = _probs(B=1, S=32, E=2, seed=3)
    C = 5
    dispatch, _, _, drops = compute_routing(probs, 1, capacity=C)
    per_expert = dispatch.sum(axis=(0, 1))          # [E, C]
    assert per_expert.shape == (2, C)
    assert float(per_expert.max()) <= 1.0           # one token per cell


def test_routing_pad_tokens_claim_no_capacity():
    # serving prefill pads prompts to a bucket: with `valid`, the pad
    # positions must route nowhere, and the real tokens' routing must
    # be IDENTICAL to routing the unpadded prefix at the same capacity
    probs = _probs(B=1, S=12, E=4, seed=7)
    L, K, C = 8, 2, 3                      # tight capacity: drops happen
    valid = jnp.arange(12)[None, :] < L
    d_pad, c_pad, aux_pad, drops_pad = compute_routing(
        probs, K, capacity=C, valid=valid)
    d_ref, c_ref, aux_ref, drops_ref = compute_routing(
        probs[:, :L], K, capacity=C)
    assert float(d_pad[:, L:].sum()) == 0.0          # pads claim nothing
    assert float(c_pad[:, L:].sum()) == 0.0
    np.testing.assert_array_equal(np.asarray(d_pad[:, :L]),
                                  np.asarray(d_ref))
    np.testing.assert_allclose(np.asarray(c_pad[:, :L]), np.asarray(c_ref),
                               rtol=1e-6)
    assert int(drops_pad) == int(drops_ref)          # pads aren't "drops"
    np.testing.assert_allclose(float(aux_pad), float(aux_ref), rtol=1e-6)


def test_moe_mlp_forward_and_grad():
    model = MoEMLP(num_experts=4, mlp_dim=16, top_k=2,
                   dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 8, 12)),
                    jnp.float32)
    params = model.init(jax.random.key(0), x)["params"]
    y, aux = model.apply({"params": params}, x)
    assert y.shape == x.shape and np.isfinite(np.asarray(y)).all()
    assert np.isfinite(float(aux))

    def loss(p):
        y, aux = model.apply({"params": p}, x)
        return (y ** 2).mean() + 0.01 * aux

    g = jax.grad(loss)(params)
    for name in ("gate", "w_in", "w_out"):
        assert float(jnp.abs(g[name]).max()) > 0, f"no grad through {name}"


def test_single_expert_equals_plain_ffn():
    """E=1, K=1, ample capacity: MoE must reduce to silu FFN exactly."""
    model = MoEMLP(num_experts=1, mlp_dim=16, top_k=1,
                   capacity_factor=2.0, dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 6, 8)),
                    jnp.float32)
    params = model.init(jax.random.key(1), x)["params"]
    y, _ = model.apply({"params": params}, x)
    w_in, w_out = params["w_in"][0], params["w_out"][0]
    want = jax.nn.silu(x @ w_in) @ w_out
    np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("ep", [1, 2])
def test_moe_transformer_trains_on_ep_mesh(ep):
    import optax

    from edl_tpu.models import TransformerConfig, TransformerLM
    from edl_tpu.models import transformer as tf_mod
    from edl_tpu.models.logical import logical_axes_from_paths
    from edl_tpu.models.transformer import lm_loss
    from edl_tpu.parallel import MeshSpec
    from edl_tpu.parallel.sharding import shard_host_batch
    from edl_tpu.train import ElasticTrainer, TrainConfig

    cfg = TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                            num_heads=4, mlp_dim=64, max_len=16,
                            dtype=jnp.float32, attention_impl="dense",
                            remat=False, moe_experts=4, moe_top_k=2)
    model = TransformerLM(cfg)

    def loss_fn(params, extra, batch, rng):
        logits, aux = model.apply({"params": params}, batch["ids"][:, :-1],
                                  with_aux=True)
        return lm_loss(logits, batch["ids"][:, 1:]) + 0.01 * aux, (
            extra, {"moe_aux": aux})

    tr = ElasticTrainer(loss_fn, TrainConfig(
        mesh_spec=MeshSpec(dp=-1, ep=ep), log_every=0))

    def init():
        return model.init(jax.random.key(0),
                          jnp.zeros((1, 8), jnp.int32))["params"], None

    shape = jax.eval_shape(lambda: init()[0])
    logical = logical_axes_from_paths(shape, tf_mod.LOGICAL_RULES)
    # expert axes resolved onto ep
    assert logical["layers"]["moe"]["w_in"] == ("layers", "expert",
                                                "embed", "expert_mlp")
    state = tr.create_state(init, optax.adam(1e-2), param_logical=logical)
    ids = np.random.default_rng(0).integers(0, 64, (8, 17)).astype(np.int32)
    batch = shard_host_batch({"ids": ids}, tr.mesh, tr.rules)
    rng = jax.random.key(1)
    first = None
    for _ in range(10):
        state, metrics = tr.step_fn(state, batch, rng)
        first = float(metrics["loss"]) if first is None else first
    last = float(metrics["loss"])
    assert np.isfinite(last) and np.isfinite(float(metrics["moe_aux"]))
    assert last < first, f"loss did not drop: {first} -> {last}"


def test_routing_reports_drop_count():
    # 1 expert, capacity 2, 6 tokens top-1: 4 assignments must drop
    probs = jnp.asarray(np.full((1, 6, 1), 1.0, np.float32))
    _, _, _, drops = compute_routing(probs, 1, capacity=2)
    assert int(drops) == 4
    _, _, _, no_drops = compute_routing(probs, 1, capacity=6)
    assert int(no_drops) == 0


def _moe_cfg(capacity_factor):
    from edl_tpu.models import TransformerConfig
    return TransformerConfig(vocab_size=64, num_layers=2, embed_dim=32,
                             num_heads=4, mlp_dim=64, max_len=32,
                             dtype=jnp.float32, attention_impl="dense",
                             remat=False, moe_experts=4, moe_top_k=2,
                             moe_capacity=capacity_factor)


def test_generate_reports_prefill_drops():
    """Serving guardrail: an under-provisioned capacity_factor yields a
    NONZERO observable drop count at prefill; ample capacity reports 0
    (and decode steps never drop by construction)."""
    import jax as _jax

    from edl_tpu.models import TransformerLM
    from edl_tpu.models.generate import generate

    starving, ample = _moe_cfg(0.05), _moe_cfg(4.0)
    params = TransformerLM(starving).init(
        _jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    prompt = jnp.asarray(np.random.default_rng(1).integers(
        0, 64, (2, 16)), jnp.int32)

    _, drops = generate(starving, params, prompt, 4, temperature=0.0,
                        return_drops=True)
    assert int(drops) > 0, "starved capacity must report drops"
    toks, no_drops = generate(ample, params, prompt, 4, temperature=0.0,
                              return_drops=True)
    assert int(no_drops) == 0
    assert toks.shape == (2, 4)


def test_decode_gather_any_top_k():
    """The drop-free gather path gates on S alone: a single-token step
    with top_k > 8 must still use it (module promise), verified against
    the capacity path with ample capacity."""
    E, K, M = 12, 10, 16
    x = jnp.asarray(np.random.default_rng(2).normal(size=(2, 1, M)),
                    jnp.float32)
    m = MoEMLP(num_experts=E, mlp_dim=32, top_k=K, capacity_factor=100.0,
               dtype=jnp.float32, decode=True)
    params = m.init(jax.random.key(0), x)
    y_gather, _ = m.apply(params, x)
    m2 = MoEMLP(num_experts=E, mlp_dim=32, top_k=K, capacity_factor=100.0,
                dtype=jnp.float32, decode=False)
    y_cap, _ = m2.apply(params, x)
    np.testing.assert_allclose(np.asarray(y_gather), np.asarray(y_cap),
                               atol=1e-5)
