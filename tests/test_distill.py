"""Distillation plane: predict pool (nop + fault injection), balance
table rebalance, discovery protocol, live teacher server end-to-end.

Mirrors reference tests distill_reader_test.py (nop 300-epoch soak →
shortened), test_distill_reader.sh (live path with real discovery), and
the balance logic of balance_table.py.
"""

import threading
import time

import numpy as np
import pytest

from edl_tpu.distill import reader as reader_mod
from edl_tpu.distill.balance import (
    NO_READY, OK, REDIRECT, UNREGISTERED, BalanceTable, Service, server_key,
)
from edl_tpu.distill.discovery import DiscoveryClient, DiscoveryServer
from edl_tpu.distill.predict_client import NopPredictClient
from edl_tpu.distill.predict_pool import PoolError, PredictPool
from edl_tpu.distill.reader import DistillReader
from edl_tpu.distill.teacher import TeacherServer
from edl_tpu.coord.register import Register


def sample_list_gen(n_batches=8, bs=4, dim=3):
    def gen():
        for b in range(n_batches):
            yield [(np.full((dim,), b * bs + i, np.float32), b * bs + i)
                   for i in range(bs)]
    return gen


def make_nop_reader(n_batches=8, bs=4, fixed=("t1", "t2"), tbs=3):
    dr = DistillReader(ins=["x", "idx"], predicts=["prediction"],
                       feeds=["x"], teacher_batch_size=tbs)
    dr.set_fixed_teacher(*fixed)
    dr.set_sample_list_generator(sample_list_gen(n_batches, bs))
    return dr


@pytest.fixture(autouse=True)
def nop_mode(monkeypatch):
    monkeypatch.setattr(reader_mod, "_NOP_PREDICT_TEST", True)
    yield


def test_nop_pool_order_and_shapes():
    dr = make_nop_reader(n_batches=10, bs=4)
    dr._pool_kw = {"manage_period": 0.05}
    batches = list(dr())
    assert len(batches) == 10
    for b, (x, idx, pred) in enumerate(batches):
        assert x.shape == (4, 3) and idx.shape == (4,) and pred.shape == (4, 1)
        # order preserved: batch b carries global ids [4b, 4b+4)
        np.testing.assert_array_equal(idx, np.arange(4 * b, 4 * b + 4))
        np.testing.assert_array_equal(x[:, 0], idx.astype(np.float32))


def test_nop_soak_multi_epoch():
    dr = make_nop_reader(n_batches=6, bs=5, tbs=4)
    dr._pool_kw = {"manage_period": 0.05}
    for _ in range(10):  # reference soaked 300 epochs; keep CI fast
        assert sum(len(b[0]) for b in dr()) == 30


def test_pool_fault_injection_requeues():
    """A teacher failing every Nth call loses its worker; the manager
    re-attaches it and every task still completes exactly once."""
    clients = []

    def factory(ep):
        c = NopPredictClient(ep, ["prediction"], fail_every=5)
        clients.append(c)
        return c

    stream_batches = [(i, [(np.ones(2, np.float32) * (4 * i + j), 4 * i + j)
                           for j in range(4)]) for i in range(12)]
    pool = PredictPool(factory, lambda: ["t1", "t2"], ["x"], [0],
                       teacher_batch_size=3, manage_period=0.05,
                       no_teacher_timeout=10.0)
    out = list(pool.run(iter(stream_batches), ["prediction"]))
    assert len(out) == 12
    ids = np.concatenate([b[1] for b in out])
    np.testing.assert_array_equal(ids, np.arange(48))
    assert len(clients) > 2  # workers died and were re-attached


def test_pool_starvation_times_out():
    def factory(ep):
        raise ConnectionError("nobody home")

    pool = PredictPool(factory, lambda: ["t1"], ["x"], [0],
                       manage_period=0.05, no_teacher_timeout=0.5)
    stream = iter([(0, [(np.ones(2, np.float32), 0)])])
    with pytest.raises(PoolError, match="no live teacher"):
        list(pool.run(stream, ["prediction"]))


# -- balance table -----------------------------------------------------------

def test_service_rebalance_spreads_load(memkv):
    svc = Service("svc", memkv, period=0.05)
    try:
        for t in ("t1", "t2", "t3", "t4"):
            memkv.put(server_key("svc", t), t.encode())
        for c in range(8):
            svc.add_client(f"c{c}", require_num=4)
        svc._refresh_servers()
        # 8 clients / 4 teachers: every client gets max(1, 4//8)=1 teacher,
        # each teacher serves ceil(8/4)=2 clients
        loads = {}
        for c in range(8):
            _, servers = svc.get_servers(f"c{c}", -1)
            assert len(servers) == 1
            loads[servers[0]] = loads.get(servers[0], 0) + 1
        assert all(v == 2 for v in loads.values())
    finally:
        svc.close()


def test_service_rebalance_many_teachers_few_clients(memkv):
    svc = Service("svc2", memkv, period=0.05)
    try:
        for t in range(6):
            memkv.put(server_key("svc2", f"t{t}"), b"x")
        svc.add_client("c0", require_num=2)
        svc.add_client("c1", require_num=99)
        svc._refresh_servers()
        _, s0 = svc.get_servers("c0", -1)
        _, s1 = svc.get_servers("c1", -1)
        assert len(s0) == 2            # capped by require_num
        assert len(s1) == 3            # capped by floor(6/2)
        assert not (set(s0) & set(s1)) or True  # overlap allowed at low load
    finally:
        svc.close()


def test_service_version_advances_only_on_change(memkv):
    svc = Service("svc3", memkv, period=0.05)
    try:
        memkv.put(server_key("svc3", "t1"), b"x")
        svc.add_client("c0", require_num=1)
        svc._refresh_servers()
        v1, servers = svc.get_servers("c0", -1)
        assert servers == ["t1"]
        v2, none = svc.get_servers("c0", v1)
        assert v2 == v1 and none is None
        memkv.put(server_key("svc3", "t2"), b"x")
        svc._refresh_servers()
        v3, servers3 = svc.get_servers("c0", v1)
        # client had its single slot already; set may or may not change,
        # but the protocol invariant holds: same version ⇒ no list
        if v3 == v1:
            assert servers3 is None
    finally:
        svc.close()


def test_balance_redirect_between_two_tables(memkv):
    ta = BalanceTable(memkv, "hostA:1")
    memkv.put(server_key("__balance__", "hostA:1"), b"x")
    memkv.put(server_key("__balance__", "hostB:2"), b"x")
    tb = BalanceTable(memkv, "hostB:2")
    ta._refresh_ring()
    tb._refresh_ring()
    try:
        # each service name is owned by exactly one of the two tables
        svc = "some-service"
        owners = {ta.owner_of(svc), tb.owner_of(svc)}
        assert len(owners) == 1
        owner = owners.pop()
        owning, other = (ta, tb) if owner == "hostA:1" else (tb, ta)
        assert other.register_client("c0", svc)["code"] == REDIRECT
        assert owning.register_client("c0", svc)["code"] == OK
    finally:
        ta.close()
        tb.close()


# -- live end-to-end ---------------------------------------------------------

def test_live_teacher_discovery_end_to_end(memkv, monkeypatch):
    """Real RPC teacher + discovery server + DistillReader, no fakes."""
    monkeypatch.setattr(reader_mod, "_NOP_PREDICT_TEST", False)
    W = np.arange(6, dtype=np.float32).reshape(3, 2)

    def predict_fn(feed):
        return {"logits": feed["x"] @ W}

    teacher = TeacherServer(predict_fn, buckets=(2, 4, 8))
    disc = DiscoveryServer(memkv, ttl=2.0)
    teacher.register(memkv, "lin-svc", ttl=2.0)
    try:
        dr = DistillReader(ins=["x", "idx"], predicts=["logits"],
                           feeds=["x"], teacher_batch_size=4)
        dr.set_dynamic_teacher(disc.endpoint, "lin-svc", max_teachers=2)
        dr.set_sample_list_generator(sample_list_gen(n_batches=5, bs=3))
        dr._pool_kw = {"manage_period": 0.1, "no_teacher_timeout": 30.0}
        batches = list(dr())
        assert len(batches) == 5
        for x, idx, logits in batches:
            np.testing.assert_allclose(logits, x @ W, rtol=1e-6)
    finally:
        teacher.stop()
        disc.stop()


def test_client_gc_reassigns_dead_students_teachers(memkv):
    """A student that dies silently (no unregister) is expired after the
    client TTL and its teachers are rebalanced to the survivors
    (reference balance_table.py:466-493 timing-wheel GC).  Driven
    through the BalanceTable RPC surface, including the
    expired-mid-heartbeat UNREGISTERED path."""
    table = BalanceTable(memkv, "ep-gc", client_ttl=1.5)
    try:
        for t in ("t1", "t2"):
            memkv.put(server_key("svc-gc", t), t.encode())
        assert table.register_client("alive", "svc-gc", require_num=2)["code"] == OK
        assert table.register_client("dead", "svc-gc", require_num=2)["code"] == OK
        table.service("svc-gc")._refresh_servers()
        # 2 clients / 2 teachers: one teacher each
        r = table.heartbeat("alive", "svc-gc", -1)
        assert r["code"] == OK and len(r["servers"]) == 1, r
        # "alive" heartbeats every 100ms (TTL/15); "dead" goes silent
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            r = table.heartbeat("alive", "svc-gc", -1)
            if r["code"] == OK and len(r.get("servers") or []) == 2:
                break
            time.sleep(0.1)
        assert r["code"] == OK and len(r["servers"]) == 2, r
        # the dead client's next heartbeat is told to re-register
        assert table.heartbeat("dead", "svc-gc", -1)["code"] == UNREGISTERED
    finally:
        table.close()


def test_timeline_profiler_env_gated(monkeypatch, capsys):
    from edl_tpu.distill import timeline as tl

    monkeypatch.setattr(tl, "_instance", None)
    monkeypatch.delenv("EDL_TPU_DISTILL_PROFILE", raising=False)
    assert not tl.timeline().enabled

    monkeypatch.setattr(tl, "_instance", None)
    monkeypatch.setenv("EDL_TPU_DISTILL_PROFILE", "1")
    t = tl.timeline()
    assert t.enabled
    with t.span("predict", teacher="t1", n=4):
        pass
    err = capsys.readouterr().err
    assert "[timeline] op=predict" in err and "teacher=t1" in err
    monkeypatch.setattr(tl, "_instance", None)
