"""Headline benchmark: ResNet50 ImageNet-shape training throughput.

Mirrors the reference's headline number (README.md:83 — ResNet50_vd
1828 img/s on 8×V100 ≈ 228.5 img/s per chip; BASELINE.md) measured as
img/s per chip on the real TPU, synthetic NHWC 224×224 data, bf16
compute, SGD momentum — the same workload shape as
example/collective/resnet50/train_with_fleet.py.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_S_PER_CHIP = 1828 / 8  # README.md:83, 8×V100


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    from edl_tpu.models import ResNet50
    from edl_tpu.train.state import TrainState

    n_dev = len(jax.devices())
    per_dev_bs = 128
    bs = per_dev_bs * n_dev
    model = ResNet50(num_classes=1000)

    rng = jax.random.key(0)
    images = jnp.asarray(np.random.default_rng(0).normal(
        size=(bs, 224, 224, 3)), jnp.bfloat16)
    labels = jnp.asarray(np.random.default_rng(1).integers(0, 1000, (bs,)))

    variables = model.init(rng, images[:2], train=False)
    tx = optax.sgd(0.1, momentum=0.9, nesterov=True)
    state = TrainState.create(variables["params"], tx,
                              extra=variables["batch_stats"])

    @jax.jit
    def step(state, images, labels):
        def lf(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": state.extra}, images,
                train=True, mutable=["batch_stats"])
            onehot = jax.nn.one_hot(labels, 1000)
            loss = -jnp.mean(jnp.sum(onehot * jax.nn.log_softmax(logits), -1))
            return loss, mutated["batch_stats"]
        (loss, new_stats), grads = jax.value_and_grad(lf, has_aux=True)(state.params)
        return state.apply_gradients(grads, new_stats), loss

    # warmup / compile; float() is the hard sync — block_until_ready does
    # not reliably drain the axon remote-execution tunnel
    state, loss = step(state, images, labels)
    float(loss)

    n_steps = 20
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = step(state, images, labels)
    float(loss)  # sync: the state chain forces all steps to have run
    dt = time.perf_counter() - t0

    img_s = bs * n_steps / dt
    img_s_per_chip = img_s / n_dev
    print(json.dumps({
        "metric": "resnet50_train_img_s_per_chip",
        "value": round(img_s_per_chip, 1),
        "unit": "img/s/chip (bf16, bs 128/chip, synthetic 224x224)",
        "vs_baseline": round(img_s_per_chip / BASELINE_IMG_S_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
