"""Driver entry: delegates to the packaged benchmark (edl_tpu/bench.py,
also installed as the `edl-bench` console script)."""

from edl_tpu.bench import main

if __name__ == "__main__":
    main()
